"""Frontier-wave learner ≡ sequential compact learner.

The wave learner (`learner_wave.py`) batches leaf-wise growth into
speculative frontier waves and trims back to exact best-first semantics
with a greedy replay.  With ``tpu_sort_cutoff=0`` the sequential compact
learner compacts every window too, and the two must agree BIT-EXACTLY
(same split sequence, same histograms, same leaf values); with the default
cutoff the physical row alignment differs so agreement is to float
tolerance.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.learner_wave import WaveTPUTreeLearner


def _train(params, X, y, rounds=5, **dskw):
    ds = lgb.Dataset(X, label=y, params=params, **dskw)
    bst = lgb.Booster(params, ds)
    for _ in range(rounds):
        bst.update()
    return bst


def _models_equal(pa, pb, X, y, rounds=5, exact=True, **dskw):
    a = _train(pa, X, y, rounds, **dskw)
    b = _train(pb, X, y, rounds, **dskw)
    assert isinstance(b.gbdt.learner, WaveTPUTreeLearner), \
        type(b.gbdt.learner).__name__
    if exact:
        assert a.model_to_string() == b.model_to_string()
    else:
        a.model_to_string(), b.model_to_string()  # flush lazy assembly
        for ta, tb in zip(a.gbdt._models, b.gbdt._models):
            np.testing.assert_array_equal(ta.split_feature, tb.split_feature)
            np.testing.assert_array_equal(ta.threshold_in_bin,
                                          tb.threshold_in_bin)
            np.testing.assert_allclose(
                ta.leaf_value[:ta.num_leaves], tb.leaf_value[:tb.num_leaves],
                rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(a.predict(X), b.predict(X), rtol=1e-4,
                               atol=1e-5)
    return a, b


def _pair(**over):
    # opening OFF for the bit-exact contract: the compact comparator keeps
    # canonical (leaf-compacted) row order at every step, while opening
    # sums the first levels' histograms in ROOT row order — same splits,
    # last-ulp f32 differences (dedicated opening tests below)
    # stall_batch=1 for the same reason: batched (K>1) replay corrections
    # histogram the stalled leaf through its parent's covering span with a
    # lid mask (parent row order) instead of a compacted child window —
    # same rows, last-ulp f32 summation differences (dedicated tolerance
    # test below)
    base = {"objective": "binary", "num_leaves": 31, "max_bin": 63,
            "min_data_in_leaf": 20, "verbosity": -1, "metric": "none",
            "tpu_sort_cutoff": 0, "tpu_wave_sort_cutoff": 0,
            "tpu_wave_open_levels": 0, "tpu_wave_defer_sorts": False,
            "tpu_wave_stall_batch": 1}
    base.update(over)
    return dict(base, tpu_learner="compact"), dict(base, tpu_learner="wave")


def _make(n=20000, f=10, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + X[:, 1] * X[:, 2] + 0.3 * rng.randn(n) > 0).astype(float)
    return X, y


def test_wave_binary_exact():
    X, y = _make()
    _models_equal(*_pair(), X=X, y=y)


def test_wave_default_cutoff_tolerance():
    # with the default sort cutoff the compact learner's small windows are
    # mask-mode (different summation alignment) — same splits, float-level
    # leaf values
    X, y = _make()
    pa, pb = _pair()
    for p in (pa, pb):
        del p["tpu_sort_cutoff"], p["tpu_wave_sort_cutoff"]
    _models_equal(pa, pb, X, y, exact=False)


@pytest.mark.parametrize("defer", [False, True])
def test_wave_stall_batch_tolerance(defer):
    # batched replay corrections (the tpu_wave_stall_batch=4 default) mask
    # the stalled leaf's histogram through its parent's span instead of a
    # compacted window — same split structure, float-level value drift;
    # low overshoot forces plenty of stalls so the batch path really runs.
    # defer=True covers the SHIPPED default combination, where batched
    # corrections read phys_i covering spans of sort-deferred children and
    # the pre-replay materialization sort is skipped
    X, y = _make()
    _, pb = _pair(tpu_wave_overshoot=0.0, tpu_wave_defer_sorts=defer)
    pb2 = dict(pb, tpu_wave_stall_batch=4)
    del pb2["tpu_sort_cutoff"], pb2["tpu_wave_sort_cutoff"]
    del pb["tpu_sort_cutoff"], pb["tpu_wave_sort_cutoff"]
    _models_equal(pb, pb2, X, y, exact=False)


def test_wave_bagging_feature_fraction():
    X, y = _make()
    pa, pb = _pair(bagging_fraction=0.6, bagging_freq=1,
                   feature_fraction=0.7, seed=7)
    _models_equal(pa, pb, X, y)


def test_wave_regression_l1_and_leaf_partition():
    # regression_l1 renews leaf outputs through the learner's leaf_id
    # partition — exercises the wave learner's speculative-leaf remap
    rng = np.random.RandomState(5)
    X = rng.randn(8000, 8)
    y = X[:, 0] * 2 + np.abs(X[:, 1]) + 0.1 * rng.randn(8000)
    pa, pb = _pair(objective="regression_l1", num_leaves=63)
    _models_equal(pa, pb, X, y)


def test_wave_monotone():
    rng = np.random.RandomState(11)
    X = rng.randn(6000, 5)
    y = 2 * X[:, 0] - X[:, 1] + 0.2 * rng.randn(6000)
    pa, pb = _pair(objective="regression",
                   monotone_constraints=[1, -1, 0, 0, 0])
    _models_equal(pa, pb, X, y)


def test_wave_categorical():
    rng = np.random.RandomState(13)
    n = 12000
    Xn = rng.randn(n, 3)
    c1 = rng.randint(0, 12, n)
    c2 = rng.randint(0, 40, n)
    X = np.column_stack([Xn, c1, c2])
    y = ((c1 % 3 == 0).astype(float) * 1.5 + Xn[:, 0]
         + (c2 > 20) + 0.3 * rng.randn(n) > 1).astype(float)
    pa, pb = _pair(max_cat_to_onehot=8)
    _models_equal(pa, pb, X, y, categorical_feature=[3, 4])


def test_wave_efb_bundles():
    rng = np.random.RandomState(17)
    n = 10000
    dense = rng.randn(n, 2)
    # mutually exclusive sparse block -> bundled by EFB
    sparse = np.zeros((n, 6))
    which = rng.randint(0, 6, n)
    rows = np.arange(n)
    sparse[rows, which] = rng.rand(n)
    sparse[rng.rand(n) < 0.5, :] = 0.0
    X = np.column_stack([dense, sparse])
    y = (dense[:, 0] + sparse.sum(1) + 0.2 * rng.randn(n) > 0.5).astype(float)
    pa, pb = _pair(enable_bundle=True)
    a, b = _models_equal(pa, pb, X, y)
    assert b.gbdt.learner._bundle is not None  # EFB actually active


def test_wave_multiclass():
    rng = np.random.RandomState(19)
    X = rng.randn(9000, 6)
    y = (X[:, 0] + X[:, 1] > 0).astype(int) + (X[:, 2] > 0.5).astype(int)
    pa, pb = _pair(objective="multiclass", num_class=3, num_leaves=15)
    _models_equal(pa, pb, X, y, rounds=3)


def test_wave_goss_dart():
    X, y = _make(12000)
    for boosting in ("goss", "dart"):
        pa, pb = _pair(boosting=boosting, seed=3)
        _models_equal(pa, pb, X, y, rounds=4)


def test_wave_exhausts_splits_early():
    # more leaves than splittable data: growth stops on no positive gain
    rng = np.random.RandomState(23)
    X = rng.randn(400, 4)
    y = (X[:, 0] > 0).astype(float)
    pa, pb = _pair(num_leaves=255, min_data_in_leaf=30)
    a, b = _models_equal(pa, pb, X, y, rounds=3)
    assert a.gbdt._models[0].num_leaves < 255


def test_wave_tiny_num_leaves():
    X, y = _make(4000)
    pa, pb = _pair(num_leaves=2)
    _models_equal(pa, pb, X, y, rounds=3)


def test_wave_max_depth():
    X, y = _make(10000)
    pa, pb = _pair(max_depth=4, num_leaves=63)
    _models_equal(pa, pb, X, y)


def test_wave_width_invariance():
    # the trimmed tree must not depend on the wave width
    X, y = _make(8000)
    _, p1 = _pair(tpu_wave_width=4)
    _, p2 = _pair(tpu_wave_width=64)
    a = _train(p1, X, y)
    b = _train(p2, X, y)
    assert a.model_to_string() == b.model_to_string()


def test_segment_hist_kernel_interpret():
    # the wave learner's one-call-per-wave histogram kernel vs a bincount
    # oracle, in Pallas interpret mode (runs on CPU)
    import jax.numpy as jnp
    from lightgbm_tpu.ops.hist_pallas import (build_histogram_segments,
                                              pack_bin_words)

    rng = np.random.RandomState(31)
    n, f, b = 4096, 8, 64
    bins = rng.randint(0, b, (f, n)).astype(np.uint8)
    w = rng.randn(3, n).astype(np.float32)
    lid = np.zeros(n, np.int32)
    # three disjoint windows with distinct lids, misaligned starts
    wins = [(100, 700, 5), (1000, 900, 9), (2500, 1500, 11)]
    for s, c, leaf in wins:
        lid[s:s + c] = leaf
    rb = 512
    slot_t, block_t, leaf_t = [], [], []
    for k, (s, c, leaf) in enumerate(wins):
        for blk in range(s // rb, (s + c - 1) // rb + 1):
            slot_t.append(k)
            block_t.append(blk)
            leaf_t.append(leaf)
    T = n // rb + 4
    while len(slot_t) < T:
        slot_t.append(3)
        block_t.append(0)
        leaf_t.append(-1)
    out = build_histogram_segments(
        pack_bin_words(jnp.asarray(bins)), jnp.asarray(w),
        jnp.asarray(lid), jnp.asarray(slot_t, dtype=jnp.int32),
        jnp.asarray(block_t, dtype=jnp.int32),
        jnp.asarray(leaf_t, dtype=jnp.int32),
        num_bins=b, n_slots=3, row_block=rb, nterms=0, interpret=True)
    out = np.asarray(out)
    assert out.shape == (3, f, b, 3)
    for k, (s, c, leaf) in enumerate(wins):
        m = (lid == leaf).astype(np.float64)
        for fi in range(f):
            for ch in range(3):
                ref = np.bincount(bins[fi], weights=w[ch] * m,
                                  minlength=b)[:b]
                np.testing.assert_allclose(out[k, fi, :, ch], ref,
                                           rtol=1e-5, atol=1e-4)


def test_wave_opening_first_tree_bit_exact():
    """Opening vs no-opening, ONE boosting round: the first iteration's
    gradients are dyadic rationals (grad ±0.5, hess 0.25 at score 0 —
    boost_from_average off), so f32 histogram sums are EXACT in any
    summation order — the two flows must emit bit-identical models."""
    X, y = _make()
    _, pb = _pair(boost_from_average=False)
    p_open = dict(pb, tpu_wave_open_levels=5)
    a = _train(pb, X, y, rounds=1)
    b = _train(p_open, X, y, rounds=1)
    assert isinstance(b.gbdt.learner, WaveTPUTreeLearner)
    assert b.gbdt.learner.open_levels > 0
    assert a.model_to_string() == b.model_to_string()


def test_wave_opening_matches_no_opening():
    """Multi-round: behaviorally equivalent models (opening changes the f32
    histogram summation ORDER for the first levels, so a near-tie split can
    legitimately flip by one bin in later trees — the first-tree test above
    pins exactness where sums are exact)."""
    X, y = _make()
    _, pb = _pair()
    p_open = dict(pb, tpu_wave_open_levels=5)
    a = _train(pb, X, y, rounds=5)
    b = _train(p_open, X, y, rounds=5)
    a.model_to_string(), b.model_to_string()
    for ta, tb in zip(a.gbdt._models, b.gbdt._models):
        assert ta.num_leaves == tb.num_leaves
    np.testing.assert_allclose(a.predict(X), b.predict(X), rtol=2e-3,
                               atol=2e-3)


def test_wave_opening_with_default_cutoffs_and_bagging():
    """Opening under the DEFAULT sort cutoffs + bagging + feature_fraction
    (the bench configuration's flow) stays structurally identical to the
    sequential compact learner."""
    X, y = _make()
    pa, pb = _pair(bagging_fraction=0.7, bagging_freq=1, bagging_seed=5,
                   feature_fraction=0.8)
    del pa["tpu_sort_cutoff"], pa["tpu_wave_sort_cutoff"]
    del pb["tpu_sort_cutoff"], pb["tpu_wave_sort_cutoff"]
    pb["tpu_wave_open_levels"] = 5
    _models_equal(pa, pb, X, y, exact=False)


def test_wave_opening_deep_tree_and_tiny_budget():
    # budget smaller than a full opening (num_leaves=4 -> 2 levels), and a
    # deeper-than-opening tree; both must replay to exact best-first
    X, y = _make(n=6000)
    for leaves in (4, 88):
        _, pb = _pair(num_leaves=leaves)
        p_open = dict(pb, tpu_wave_open_levels=5)
        a = _train(pb, X, y, rounds=2)
        b = _train(p_open, X, y, rounds=2)
        a.model_to_string(), b.model_to_string()
        for ta, tb in zip(a.gbdt._models, b.gbdt._models):
            assert ta.num_leaves == tb.num_leaves
        np.testing.assert_allclose(a.predict(X), b.predict(X), rtol=2e-3,
                                   atol=2e-3)


def test_wave_defer_sorts_first_tree_bit_exact():
    """Sort-deferral alternation vs per-wave sorting, ONE round with
    dyadic gradients (boost_from_average off): f32 sums are exact in any
    order, so the models must be bit-identical."""
    X, y = _make()
    _, pb = _pair(boost_from_average=False)
    p_defer = dict(pb, tpu_wave_defer_sorts=True)
    a = _train(pb, X, y, rounds=1)
    b = _train(p_defer, X, y, rounds=1)
    assert a.model_to_string() == b.model_to_string()


def test_wave_defer_sorts_matches_multi_round():
    """Multi-round behavioral equivalence under the DEFAULT cutoffs +
    bagging (deferral changes histogram summation order — near-tie bin
    flips allowed, models must stay equivalent)."""
    X, y = _make()
    _, pb = _pair(bagging_fraction=0.7, bagging_freq=1, bagging_seed=5)
    del pb["tpu_sort_cutoff"], pb["tpu_wave_sort_cutoff"]
    p_defer = dict(pb, tpu_wave_defer_sorts=True)
    a = _train(pb, X, y, rounds=5)
    b = _train(p_defer, X, y, rounds=5)
    a.model_to_string(), b.model_to_string()
    for ta, tb in zip(a.gbdt._models, b.gbdt._models):
        assert ta.num_leaves == tb.num_leaves
    np.testing.assert_allclose(a.predict(X), b.predict(X), rtol=2e-3,
                               atol=2e-3)


def test_wave_defer_sorts_deep_tree():
    X, y = _make(n=30000)
    _, pb = _pair(num_leaves=127, boost_from_average=False)
    p_defer = dict(pb, tpu_wave_defer_sorts=True)
    a = _train(pb, X, y, rounds=1)
    b = _train(p_defer, X, y, rounds=1)
    assert a.model_to_string() == b.model_to_string()


def test_multislot_hist_kernel_interpret():
    # the opening-phase full-pass kernel (K leaves in one pass, slot routing
    # in the weight operand) vs a bincount oracle, Pallas interpret mode
    import jax.numpy as jnp
    from lightgbm_tpu.ops.hist_pallas import (build_histogram_multislot,
                                              pack_bin_words)

    rng = np.random.RandomState(37)
    n, f, b, K = 4096, 8, 64, 4
    bins = rng.randint(0, b, (f, n)).astype(np.uint8)
    # channel 2 is the BAG MASK ({0,1}) by kernel contract — the mixed term
    # expansion gives it a single exact bf16 term
    bag = (rng.rand(n) < 0.7).astype(np.float32)
    w = np.stack([rng.randn(n).astype(np.float32) * bag,
                  rng.randn(n).astype(np.float32) * bag, bag])
    # interleaved slots incl. masked rows (slot == K) — root-order layout
    slot = rng.randint(0, K + 1, n).astype(np.int32)
    # interpret-mode dots carry ~single-bf16-term precision regardless of
    # nterms (a simulator artifact — the real MXU path measures ~1e-6 at
    # nterms=3), so g/h tolerances are loose at nterms=3; counts and the
    # nterms=0 (f32 HIGHEST) path must be tight
    for nterms, tol in ((0, dict(rtol=1e-5, atol=1e-3)),
                        (3, dict(rtol=2e-2, atol=5e-2))):
        out = np.asarray(build_histogram_multislot(
            pack_bin_words(jnp.asarray(bins)), jnp.asarray(w),
            jnp.asarray(slot), num_bins=b, n_slots=K, row_block=512,
            nterms=nterms, interpret=True))
        assert out.shape == (K, f, b, 3)
        for k in range(K):
            m = (slot == k).astype(np.float64)
            for fi in range(f):
                for ch in range(3):
                    ref = np.bincount(bins[fi], weights=w[ch] * m,
                                      minlength=b)[:b]
                    np.testing.assert_allclose(out[k, fi, :, ch], ref,
                                               **tol)
            np.testing.assert_array_equal(
                out[k, :, :, 2], np.rint(out[k, :, :, 2]))  # counts exact


def test_wave_exact_counts():
    X, y = _make(15000)
    _, pb = _pair(bagging_fraction=0.5, bagging_freq=1, seed=9)
    b = _train(pb, X, y, rounds=2)
    b.model_to_string()  # flush lazy assembly
    for t in b.gbdt._models:
        ni = t.num_leaves - 1
        lc = np.asarray(t.internal_count[:ni])
        for nd in range(ni):
            l, r = t.left_child[nd], t.right_child[nd]
            lcnt = t.leaf_count[~l] if l < 0 else t.internal_count[l]
            rcnt = t.leaf_count[~r] if r < 0 else t.internal_count[r]
            assert lc[nd] == lcnt + rcnt


def test_wave_chunked_rows_exact(monkeypatch):
    """The lax.map'd per-row chunk path (large-N transient bound) is
    bit-identical to the single-pass path."""
    X, y = _make(n=8192, f=6)
    pa, pb = _pair(num_leaves=15)
    import lightgbm_tpu.learner_wave as lw
    a = _train(pb, X, y)          # wave, single-pass (n < _row_chunk)
    orig = lw.WaveTPUTreeLearner.__init__

    def patched(self, *args, **kw):
        orig(self, *args, **kw)
        self._row_chunk = 1024    # force Cm > 1

    monkeypatch.setattr(lw.WaveTPUTreeLearner, "__init__", patched)
    b = _train(pb, X, y)
    assert b.gbdt.learner._row_chunk == 1024
    assert a.model_to_string() == b.model_to_string()
