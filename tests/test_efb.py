"""Exclusive Feature Bundling (EFB) — `src/io/dataset.cpp:67-213`."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.efb import find_bundles


def _sparse_exclusive(rng, n=6000, blocks=4, per_block=3):
    """blocks × per_block one-hot-ish features: inside a block exactly one
    feature is non-zero per row — perfectly exclusive."""
    cols = []
    y = np.zeros(n)
    for b in range(blocks):
        which = rng.randint(0, per_block, n)
        vals = rng.randn(n) * (1 + b)
        for j in range(per_block):
            col = np.where(which == j, vals, 0.0)
            cols.append(col)
            y += np.where(which == j, (j + 1) * col, 0.0) * 0.3
    X = np.column_stack(cols + [rng.randn(n)])   # plus one dense feature
    y += 0.5 * X[:, -1] + 0.05 * rng.randn(n)
    return X, y


def test_bundles_found_and_axis_reduced(rng):
    X, y = _sparse_exclusive(rng)
    ds = lgb.Dataset(X, label=y, params={"verbosity": -1, "max_bin": 63})
    ds.construct()
    data = ds.constructed
    # 64-bin features fit the 256-bin group cap (the reference GPU path cap)
    assert data.bundle is not None
    # 12 exclusive features + 1 dense → far fewer histogram columns
    assert data.bundle.num_groups < data.num_used_features
    assert data.bundle.max_group_bin <= 256
    groups = data.bundle.groups
    assert any(len(g) > 1 for g in groups)


def test_efb_predictions_unchanged(rng):
    """max_conflict_rate=0 bundling is lossless — the model must be
    IDENTICAL with and without bundling."""
    X, y = _sparse_exclusive(rng)
    params = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
              "min_data_in_leaf": 20, "max_bin": 63}
    with_efb = lgb.train(params, lgb.Dataset(X, label=y), 10)
    assert with_efb.gbdt.learner._bundle is not None
    without = lgb.train(dict(params, enable_bundle=False),
                        lgb.Dataset(X, label=y), 10)
    assert without.gbdt.learner._bundle is None
    np.testing.assert_allclose(with_efb.predict(X), without.predict(X),
                               rtol=1e-5, atol=1e-6)
    # identical structure, not merely similar predictions
    for ta, tb in zip(with_efb.gbdt.models, without.gbdt.models):
        np.testing.assert_array_equal(
            ta.split_feature[:ta.num_leaves - 1],
            tb.split_feature[:tb.num_leaves - 1])
        np.testing.assert_allclose(
            ta.threshold[:ta.num_leaves - 1],
            tb.threshold[:tb.num_leaves - 1], rtol=1e-12)


def test_efb_respects_conflicts(rng):
    """Features that do co-occur must NOT bundle at max_conflict_rate=0."""
    n = 4000
    a = rng.randn(n) * (rng.rand(n) < 0.5)
    b = rng.randn(n) * (rng.rand(n) < 0.5)   # overlaps with a ~25% of rows
    X = np.column_stack([a, b])
    y = a + b
    ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
    ds.construct()
    bundle = ds.constructed.bundle
    if bundle is not None:
        assert all(len(g) == 1 for g in bundle.groups)


def test_efb_valid_sets_and_missing(rng):
    X, y = _sparse_exclusive(rng)
    Xv, yv = _sparse_exclusive(rng, n=1500)
    params = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
              "min_data_in_leaf": 20, "metric": "l2", "max_bin": 63}
    ds = lgb.Dataset(X, label=y, params=params)
    dv = lgb.Dataset(Xv, label=yv, reference=ds)
    evals = {}
    bst = lgb.train(params, ds, 10, valid_sets=[dv], valid_names=["v"],
                    evals_result=evals, verbose_eval=False)
    # device valid-set traversal (per-feature bins) agrees with host predict
    want = float(np.mean((bst.predict(Xv) - yv) ** 2))
    np.testing.assert_allclose(evals["v"]["l2"][-1], want, rtol=1e-5)
