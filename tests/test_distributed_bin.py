"""Distributed bin finding + pre-partitioned loading
(`lightgbm_tpu/io/distributed.py` vs `src/io/dataset_loader.cpp:873-955`).

The done-criterion test: every simulated host bins ONLY its row shard, and
the assembled mapper table is bit-for-bit identical to single-host binning
of the full matrix.
"""

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import _ConstructedDataset
from lightgbm_tpu.io.distributed import (LoopbackCluster, _feature_ranges,
                                         distributed_construct,
                                         load_partitioned_file,
                                         partition_rows)

pytestmark = pytest.mark.fast


def _mapper_equal(a, b):
    """dict equality with NaN == NaN (the NaN bin's upper bound)."""
    da, db = a.to_dict(), b.to_dict()
    if set(da) != set(db):
        return False
    for k in da:
        va, vb = da[k], db[k]
        if isinstance(va, list):
            if not np.array_equal(np.asarray(va, np.float64),
                                  np.asarray(vb, np.float64),
                                  equal_nan=True):
                return False
        elif va != vb:
            return False
    return True


def _make_matrix(n=5000, f=11, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    X[:, 1] = np.round(X[:, 1] * 2)            # few distinct values
    X[rng.rand(n, f) < 0.2] = 0.0              # sparse zeros
    X[rng.rand(n, f) < 0.05] = np.nan          # missing
    X[:, 4] = rng.randint(0, 7, n)             # categorical-ish ints
    return X


@pytest.mark.parametrize("num_machines", [2, 3, 5])
def test_mappers_match_single_host(num_machines):
    X = _make_matrix()
    cfg = Config.from_params({"max_bin": 63, "min_data_in_bin": 3,
                              "bin_construct_sample_cnt": 2000})
    ref = _ConstructedDataset.from_matrix(X, cfg, categorical=[4])

    # contiguous row shards (the pre-partitioned layout)
    cuts = np.linspace(0, len(X), num_machines + 1).astype(int)
    shards = [(X[cuts[r]:cuts[r + 1]],) for r in range(num_machines)]
    cluster = LoopbackCluster(num_machines)
    outs = cluster.run(
        lambda net, shard: distributed_construct(net, shard, cfg,
                                                 categorical=[4]),
        shards)

    for ds in outs:
        assert len(ds.bin_mappers) == len(ref.bin_mappers)
        assert np.array_equal(ds.used_feature_map, ref.used_feature_map)
        for a, b in zip(ds.bin_mappers, ref.bin_mappers):
            assert _mapper_equal(a, b)          # bit-for-bit mapper parity

    # shard bins == the corresponding row slice of single-host binning
    for r, ds in enumerate(outs):
        n_r = cuts[r + 1] - cuts[r]
        assert ds.num_data == n_r
        assert ds.row_offset == cuts[r]
        assert ds.num_data_global == len(X)
        ours = ds.bins[:len(ds.bin_mappers), :n_r]
        want = ref.bins[:len(ref.bin_mappers), cuts[r]:cuts[r + 1]]
        np.testing.assert_array_equal(ours, want)


def test_no_host_sees_full_matrix():
    """The construction path only touches the shard each rank was given —
    peak per-rank matrix memory is the shard plus the global SAMPLE."""
    X = _make_matrix(n=3000, f=5)
    cfg = Config.from_params({"max_bin": 15,
                              "bin_construct_sample_cnt": 500})
    cluster = LoopbackCluster(3)
    cuts = np.linspace(0, len(X), 4).astype(int)
    outs = cluster.run(
        lambda net, shard: distributed_construct(net, shard, cfg),
        [(X[cuts[r]:cuts[r + 1]],) for r in range(3)])
    total = sum(ds.num_data for ds in outs)
    assert total == len(X)
    # mappers agree across ranks even though no rank saw all rows
    for ds in outs[1:]:
        assert all(_mapper_equal(a, b) for a, b in
                   zip(ds.bin_mappers, outs[0].bin_mappers))


def test_partition_rows_mod():
    idx = [set(partition_rows(10, r, 3, pre_partition=False).tolist())
           for r in range(3)]
    assert idx[0] == {0, 3, 6, 9}
    assert idx[1] == {1, 4, 7}
    assert idx[2] == {2, 5, 8}
    assert set().union(*idx) == set(range(10))
    assert partition_rows(7, 1, 3, pre_partition=True).tolist() == \
        list(range(7))


def test_feature_ranges_cover():
    for f in [1, 2, 7, 16]:
        for k in [1, 2, 3, 8]:
            start, length = _feature_ranges(f, k)
            spans = [range(s, s + max(n, 0))
                     for s, n in zip(start, length)]
            flat = [j for sp in spans for j in sp]
            assert flat == list(range(f)), (f, k, start, length)


def test_load_partitioned_file(tmp_path):
    rows = ["%d,%.3f,%.3f" % (i % 2, i * 0.1, -i) for i in range(20)]
    p = tmp_path / "part.csv"
    p.write_text("\n".join(rows) + "\n")
    params = {"header": False, "label_column": 0}
    mats = []
    for r in range(3):
        mat, label, _, _, gr = load_partitioned_file(str(p), params, r, 3)
        mats.append((mat, label))
        np.testing.assert_array_equal(gr, partition_rows(20, r, 3, False))
    # every global row appears on exactly one rank
    from lightgbm_tpu.io.parser import load_data_file
    full, full_label, _, _ = load_data_file(str(p), params)
    got = np.concatenate([m for m, _ in mats])
    assert sorted(map(tuple, got.tolist())) == \
        sorted(map(tuple, full.tolist()))


def test_load_partitioned_header_and_weights(tmp_path):
    """Mod-partition with a header line: no rank loses a data row, and the
    .weight sidecar is read from the original path and row-subset."""
    rows = ["%d,%.3f,%.3f" % (i % 3, i * 0.5, i) for i in range(13)]
    p = tmp_path / "hdr.csv"
    p.write_text("label,f0,f1\n" + "\n".join(rows) + "\n")
    (tmp_path / "hdr.csv.weight").write_text(
        "\n".join(str(0.1 * (i + 1)) for i in range(13)) + "\n")
    params = {"header": True, "label_column": 0}
    seen = []
    for r in range(2):
        mat, label, weight, group, gr = load_partitioned_file(
            str(p), params, r, 2)
        owned = partition_rows(13, r, 2, False)
        np.testing.assert_array_equal(gr, owned)
        assert len(mat) == len(owned)
        np.testing.assert_allclose(mat[:, 0], owned * 0.5)
        np.testing.assert_allclose(weight, 0.1 * (owned + 1))
        seen.extend(gr.tolist())
    assert sorted(seen) == list(range(13))


def test_mod_partition_mappers_match_single_host():
    """Interleaved (mod-partitioned) shards with explicit global_rows still
    produce mappers bit-identical to single-host binning."""
    X = _make_matrix(n=4000, f=7)
    cfg = Config.from_params({"max_bin": 31,
                              "bin_construct_sample_cnt": 1500})
    ref = _ConstructedDataset.from_matrix(X, cfg)
    k = 3
    cluster = LoopbackCluster(k)
    args = []
    for r in range(k):
        rows = partition_rows(len(X), r, k, pre_partition=False)
        args.append((X[rows], rows))
    outs = cluster.run(
        lambda net, shard, rows: distributed_construct(
            net, shard, cfg, global_rows=rows),
        args)
    for ds in outs:
        assert len(ds.bin_mappers) == len(ref.bin_mappers)
        for a, b in zip(ds.bin_mappers, ref.bin_mappers):
            assert _mapper_equal(a, b)
    # shard bins equal the single-host bins at the owned rows
    for r, ds in enumerate(outs):
        rows = partition_rows(len(X), r, k, pre_partition=False)
        np.testing.assert_array_equal(
            ds.bins[:len(ds.bin_mappers), :len(rows)],
            ref.bins[:len(ref.bin_mappers), rows])


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.parametrize("num_machines", [2, 3])
def test_socket_net_multiprocess_mappers_match_single_host(
        tmp_path, num_machines):
    """Round-4 verdict item 4: the loopback threads are no longer the only
    transport — N REAL PROCESSES bin mod-partitioned shards of a real data
    file over the TCP ``SocketNet`` (`io/net.py`, the role of
    `src/network/linkers_socket.cpp:77-218`), and every process ends with
    the bit-identical global mapper table."""
    import pickle
    import subprocess
    import sys as _sys

    from lightgbm_tpu.binning import BinMapper
    from lightgbm_tpu.io.parser import load_data_file

    X = _make_matrix(n=3000, f=8)
    y = (np.nansum(X[:, :2], axis=1) > 0).astype(float)
    data_path = str(tmp_path / "train.csv")
    with open(data_path, "w") as fh:
        for i in range(len(X)):
            row = [f"{y[i]:g}"] + [("nan" if np.isnan(v)
                                    else repr(float(v))) for v in X[i]]
            fh.write(",".join(row) + "\n")

    port = _free_port()
    worker = str(__import__("pathlib").Path(__file__).parent
                 / "_socket_net_worker.py")
    procs, outs = [], []
    for r in range(num_machines):
        out = str(tmp_path / f"out_{r}.pkl")
        outs.append(out)
        procs.append(subprocess.Popen(
            [_sys.executable, worker, str(r), str(num_machines), str(port),
             data_path, out],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    for p in procs:
        _stdout, stderr = p.communicate(timeout=300)
        assert p.returncode == 0, stderr.decode()[-2000:]
    results = [pickle.load(open(o, "rb")) for o in outs]

    # single-host oracle over the same file/params
    params = {"max_bin": 63, "min_data_in_bin": 3,
              "bin_construct_sample_cnt": 2000, "label_column": "0"}
    mat, _l, _w, _g = load_data_file(data_path, params)
    ref = _ConstructedDataset.from_matrix(
        mat, Config.from_params(params), categorical=[4])

    for res in results:
        assert np.array_equal(res["used"], ref.used_feature_map)
        assert res["num_data_global"] == len(mat)
        for d, b in zip(res["mappers"], ref.bin_mappers):
            assert _mapper_equal(BinMapper.from_dict(d), b)
        # the mod-partitioned shard's bins == the owned rows of the
        # single-host binning
        want = ref.bins[:len(ref.bin_mappers), :len(mat)][:,
                                                          res["global_rows"]]
        np.testing.assert_array_equal(res["bins"], want)
    # no row lost or duplicated across the partition
    all_rows = np.sort(np.concatenate([r["global_rows"] for r in results]))
    np.testing.assert_array_equal(all_rows, np.arange(len(mat)))


def test_query_aware_mod_partition_distributed_lambdarank(tmp_path):
    """Round-4 verdict item 8 (`Metadata::CheckOrPartition`): a
    mod-partition with a ``.query`` sidecar deals WHOLE query groups, and
    distributed lambdarank on the dealt data reproduces single-host NDCG."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.io.distributed import partition_queries

    rng = np.random.RandomState(5)
    nq = 120
    sizes = rng.randint(3, 12, nq)
    n = int(sizes.sum())
    X = rng.randn(n, 6)
    qid = np.repeat(np.arange(nq), sizes)
    rel = np.clip((X[:, 0] + 0.5 * X[:, 1]
                   + 0.3 * rng.randn(n) > 0.5).astype(int)
                  + (X[:, 2] > 1).astype(int) * 2, 0, 4)
    path = str(tmp_path / "rank.train")
    with open(path, "w") as fh:
        for i in range(n):
            fh.write(",".join([f"{rel[i]:d}"]
                              + [repr(float(v)) for v in X[i]]) + "\n")
    with open(path + ".query", "w") as fh:
        fh.write("\n".join(str(s) for s in sizes) + "\n")

    M = 3
    params = {"max_bin": 63, "min_data_in_bin": 3, "label_column": "0",
              "bin_construct_sample_cnt": 2000}
    cfg = Config.from_params(params)
    shards = [load_partitioned_file(path, params, r, M) for r in range(M)]

    # -- dealing properties: whole groups, full cover, no duplicates
    starts = np.concatenate([[0], np.cumsum(sizes)])
    for r, (mat, label, weight, group, rows) in enumerate(shards):
        assert int(np.sum(group)) == len(mat) == len(rows)
        owned_rows, owned_sizes = partition_queries(sizes, r, M)
        np.testing.assert_array_equal(rows, owned_rows)
        np.testing.assert_array_equal(group, owned_sizes)
        # every owned query's rows are contiguous and complete
        for q in range(r, nq, M):
            assert np.all(np.isin(
                np.arange(starts[q], starts[q + 1]), rows))
    allr = np.sort(np.concatenate([s[4] for s in shards]))
    np.testing.assert_array_equal(allr, np.arange(n))

    # -- mappers identical to single-host despite the query dealing
    cluster = LoopbackCluster(M)
    outs = cluster.run(
        lambda net, mat, label, group, rows: distributed_construct(
            net, mat, cfg, label=label, group=group, global_rows=rows),
        [(s[0], s[1], s[3], s[4]) for s in shards])
    from lightgbm_tpu.io.parser import load_data_file
    mat_full, _l, _w, _g = load_data_file(path, params)
    ref = _ConstructedDataset.from_matrix(mat_full, cfg)
    for ds in outs:
        assert len(ds.bin_mappers) == len(ref.bin_mappers)
        for a, b in zip(ds.bin_mappers, ref.bin_mappers):
            assert _mapper_equal(a, b)
        assert int(np.sum(ds.metadata.query_boundaries[-1])) == ds.num_data

    # -- NDCG parity: serial lambdarank on the ORIGINAL order vs
    # tree_learner=data on the query-dealt order (same queries, whole)
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device (virtual) mesh")
    from lightgbm_tpu.parallel.learners import apply_parallel_sharding
    from lightgbm_tpu.parallel.mesh import make_mesh

    tp = {"objective": "lambdarank", "metric": "ndcg", "eval_at": "5",
          "num_leaves": 15, "min_data_in_leaf": 5, "verbosity": -1,
          "gpu_use_dp": True, "learning_rate": 0.1}

    def ndcg(Xm, ym, grp, mode):
        ds = lgb.Dataset(Xm, label=ym, group=grp, params=tp)
        ds.construct()
        bst = lgb.Booster(dict(tp, tree_learner=mode), ds)
        if mode != "serial":
            apply_parallel_sharding(bst.gbdt, make_mesh(), mode)
        for _ in range(5):
            bst.update()
        out = bst.eval_train()
        return dict((name, v) for _, name, v, _ in out)

    s = ndcg(mat_full[:, :], rel.astype(float), sizes, "serial")
    Xr = np.concatenate([sh[0] for sh in shards])
    yr = np.concatenate([sh[1] for sh in shards])
    gr = np.concatenate([sh[3] for sh in shards])
    d = ndcg(Xr, yr, gr, "data")
    for k in s:
        assert abs(s[k] - d[k]) < 1e-6, (k, s[k], d[k])


@pytest.mark.parametrize("num_machines", [2, 3])
def test_distributed_efb_bundles_rank_identical(num_machines):
    """Round-4 missing item 4: EFB bundles are now derived from the
    allgathered GLOBAL sample, so every rank computes the IDENTICAL greedy
    grouping (the reference's FastFeatureBundling-over-sample,
    `src/io/dataset.cpp:139`) — no rank disagreement, regardless of the
    row sharding."""
    rng = np.random.RandomState(9)
    n = 4000
    dense = rng.randn(n, 2)
    sparse = np.zeros((n, 6))
    sparse[np.arange(n), rng.randint(0, 6, n)] = rng.rand(n)
    sparse[rng.rand(n) < 0.5, :] = 0.0
    X = np.column_stack([dense, sparse])
    cfg = Config.from_params({"max_bin": 63, "enable_bundle": True,
                              "bin_construct_sample_cnt": 2000})

    cuts = np.linspace(0, n, num_machines + 1).astype(int)
    shards = [(X[cuts[r]:cuts[r + 1]],) for r in range(num_machines)]
    outs = LoopbackCluster(num_machines).run(
        lambda net, shard: distributed_construct(net, shard, cfg), shards)
    assert all(o.bundle is not None for o in outs)
    g0 = outs[0].bundle.groups
    assert any(len(g) > 1 for g in g0)       # the sparse block bundled
    for o in outs[1:]:
        assert o.bundle.groups == g0
        np.testing.assert_array_equal(o.bundle.f_gcol,
                                      outs[0].bundle.f_gcol)
        np.testing.assert_array_equal(o.bundle.f_off,
                                      outs[0].bundle.f_off)

    # mod-partitioned shards (different local row sets) agree too
    outs2 = LoopbackCluster(num_machines).run(
        lambda net, shard, rows: distributed_construct(
            net, shard, cfg, global_rows=rows),
        [(X[r::num_machines],
          np.arange(r, n, num_machines, dtype=np.int64))
         for r in range(num_machines)])
    for o in outs2:
        assert o.bundle is not None and o.bundle.groups == g0


def test_socket_net_from_config(tmp_path):
    """The reference config surface (machine_list_filename /
    local_listen_port / time_out) builds the construction net."""
    from lightgbm_tpu.io.net import net_from_config, parse_machine_list

    ml = tmp_path / "mlist.txt"
    ml.write_text("# master first\n127.0.0.1 45871\n127.0.0.1 45872\n")
    assert parse_machine_list(str(ml)) == [("127.0.0.1", 45871),
                                           ("127.0.0.1", 45872)]
    cfg = Config.from_params({"num_machines": 1,
                              "machine_list_filename": str(ml)})
    net = net_from_config(cfg, 0)       # single machine: no sockets open
    assert net.allgather("x") == ["x"]
    net.close()
    cfg3 = Config.from_params({"num_machines": 3,
                               "machine_list_filename": str(ml)})
    with pytest.raises(ValueError, match="machine list"):
        net_from_config(cfg3, 0)
