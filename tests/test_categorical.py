"""Categorical splits end-to-end — the analogue of the reference's
pandas-categorical engine tests (`tests/python_package_test/test_engine.py:217-290`).

Golden numbers produced by the reference CLI (built from /root/reference,
see `.claude/skills/verify/SKILL.md`) on the synthetic dataset below with
`categorical_feature=0,2 num_trees=10 num_leaves=31 learning_rate=0.1
min_data_in_leaf=20 max_bin=255`:

    Iteration:5,  training l2 : 1.58616
    Iteration:10, training l2 : 0.704366
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb

GOLDEN = {5: 1.58616, 10: 0.704366}


def _make_data(path=None):
    rng = np.random.RandomState(42)
    n = 2000
    c_small = rng.randint(0, 4, n)        # one-hot scan regime
    num = rng.randn(n)
    c_big = rng.randint(0, 25, n)         # sorted-CTR many-vs-many regime
    eff_s = np.array([0.5, -1.0, 2.0, -0.3])
    eff_b = rng.randn(25) * 1.5
    y = eff_s[c_small] + 0.8 * num + eff_b[c_big] + 0.3 * rng.randn(n)
    X = np.column_stack([c_small.astype(np.float64), num,
                         c_big.astype(np.float64)])
    if path is not None:
        with open(path, "w") as f:
            for yi, r in zip(y, X):
                f.write(f"{yi:.9g}\t{int(r[0])}\t{r[1]:.9g}\t{int(r[2])}\n")
    return X, y


PARAMS = {"objective": "regression", "metric": "l2", "num_leaves": 31,
          "learning_rate": 0.1, "min_data_in_leaf": 20, "max_bin": 255,
          "verbosity": -1, "is_training_metric": True}


def test_categorical_golden_vs_reference_cli(tmp_path):
    path = tmp_path / "cat.train"
    _make_data(str(path))
    ds = lgb.Dataset(str(path), params={"max_bin": 255,
                                        "categorical_feature": "0,2"})
    params = dict(PARAMS, gpu_use_dp=True)
    evals = {}
    lgb.train(params, ds, 10, valid_sets=[ds], evals_result=evals,
              verbose_eval=False)
    for it, want in GOLDEN.items():
        got = evals["training"]["l2"][it - 1]
        assert abs(got - want) < 1e-5 * max(1.0, want), (it, got, want)


def test_categorical_learner_parity_and_roundtrip():
    X, y = _make_data()
    models = {}
    for learner in ("compact", "masked"):
        ds = lgb.Dataset(X, label=y, categorical_feature=[0, 2])
        bst = lgb.train(dict(PARAMS, tpu_learner=learner), ds, 8)
        models[learner] = bst
    p_c = models["compact"].predict(X)
    p_m = models["masked"].predict(X)
    np.testing.assert_allclose(p_c, p_m, rtol=1e-4, atol=1e-5)
    # model-text round trip preserves categorical predictions exactly
    bst2 = lgb.Booster(model_str=models["compact"].model_to_string())
    np.testing.assert_allclose(bst2.predict(X), p_c, rtol=0, atol=0)
    assert models["compact"].gbdt.models[0].num_cat > 0


def test_categorical_device_vs_host_traversal():
    """Valid-set score updates traverse on device (bitset membership) — must
    match the host predictor."""
    X, y = _make_data()
    ds = lgb.Dataset(X[:1500], label=y[:1500], categorical_feature=[0, 2])
    dv = lgb.Dataset(X[1500:], label=y[1500:], reference=ds)
    evals = {}
    bst = lgb.train(dict(PARAMS), ds, 8, valid_sets=[dv],
                    valid_names=["v"], evals_result=evals, verbose_eval=False)
    pred = bst.predict(X[1500:])
    want_l2 = float(np.mean((pred - y[1500:]) ** 2))
    got_l2 = evals["v"]["l2"][-1]
    np.testing.assert_allclose(got_l2, want_l2, rtol=1e-5)


def test_continue_training_with_categoricals(tmp_path):
    X, y = _make_data()
    ds = lgb.Dataset(X, label=y, categorical_feature=[0, 2])
    bst = lgb.train(dict(PARAMS), ds, 4)
    path = tmp_path / "model.txt"
    bst.save_model(str(path))
    ds2 = lgb.Dataset(X, label=y, categorical_feature=[0, 2])
    bst2 = lgb.train(dict(PARAMS), ds2, 4, init_model=str(path))
    assert bst2.num_trees() == 8
    # the reloaded model's categorical splits traverse correctly (rebind
    # rebuilt the inner bitsets)
    p = bst2.predict(X)
    assert np.mean((p - y) ** 2) < np.mean((bst.predict(X) - y) ** 2)
