"""Tracing & service metrics: span recorder semantics, Chrome JSON
export, trace_id propagation through a live server, exact histogram
percentiles, Prometheus export, periodic stats snapshots, tracing-off
no-op invariants, and the bench_serving.py contract."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.observability import (BENCH_SERVING_SCHEMA,
                                        LatencyHistogram, TraceRecorder,
                                        new_trace_id, validate_report)
from lightgbm_tpu.observability.metrics_export import prometheus_text
from lightgbm_tpu.serving import ServerOverloaded, ServingClient


def _train(rng, trees=8, n=2000, f=6, **params):
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "min_data_in_leaf": 10}
    p.update(params)
    return lgb.train(p, lgb.Dataset(X, label=y), trees)


# -- recorder semantics ------------------------------------------------------

def test_span_nesting_and_ring_wrap():
    r = TraceRecorder(True, capacity=4)
    with r.span("outer", args={"k": 1}):
        with r.span("mid"):
            with r.span("inner"):
                pass
    ev = [e for e in r.export()["traceEvents"] if e["ph"] in "BE"]
    # B/E pairs, properly nested: outer opens first, closes last
    assert [(e["ph"], e["name"]) for e in ev] == [
        ("B", "outer"), ("B", "mid"), ("B", "inner"),
        ("E", "inner"), ("E", "mid"), ("E", "outer")]
    # ring wrap: capacity 4, 3 already recorded, 10 more overwrite oldest
    for i in range(10):
        with r.span(f"s{i}"):
            pass
    assert len(r) == 4
    assert r.dropped == 9
    names = {s[0] for s in r.spans()}
    assert names == {"s6", "s7", "s8", "s9"}   # newest 4 survive


def test_chrome_trace_json_loads_and_pairs_be():
    r = TraceRecorder(True)
    for i in range(5):
        with r.span(f"work{i % 2}", cat="test", trace_id=f"t{i}"):
            pass
    r.instant("marker", args={"note": "x"})
    exported = r.export()
    # round-trips as plain JSON (the Perfetto/chrome://tracing contract)
    trace = json.loads(json.dumps(exported))
    assert trace["displayTimeUnit"] == "ms"
    evs = trace["traceEvents"]
    per_key = {}
    for e in evs:
        assert e["ph"] == "M" or isinstance(e["ts"], (int, float))
        if e["ph"] in "BE":
            key = (e["tid"], e["name"])
            per_key.setdefault(key, [0, 0])
            per_key[key][0 if e["ph"] == "B" else 1] += 1
    assert per_key and all(b == e for b, e in per_key.values())
    assert any(e["ph"] == "i" and e["name"] == "marker" for e in evs)
    # every B span carries its trace_id in args
    b_ids = {e["args"]["trace_id"] for e in evs if e["ph"] == "B"}
    assert b_ids == {f"t{i}" for i in range(5)}


def test_disabled_recorder_records_nothing():
    r = TraceRecorder(False)
    with r.span("x"):
        pass
    r.add_complete("y", 0.0, 1.0)
    r.instant("z")
    assert len(r) == 0 and r.dropped == 0
    assert r.export()["traceEvents"] == []


def test_bind_propagates_trace_id_across_helpers():
    r = TraceRecorder(True)
    with r.bind("req-1"):
        with r.span("stage"):
            pass
    with r.span("unbound"):
        pass
    spans = {s[0]: s[6] for s in r.spans()}
    assert spans["stage"] == "req-1"
    assert spans["unbound"] is None


# -- histogram / Prometheus --------------------------------------------------

def test_histogram_percentiles_exact_vs_numpy(rng):
    h = LatencyHistogram()
    xs = rng.lognormal(mean=0.5, sigma=1.2, size=5000)   # < window
    for x in xs:
        h.record(x)
    got = h.percentiles((50, 95, 99))
    want = np.percentile(xs, [50, 95, 99])
    np.testing.assert_allclose(
        [got["p50"], got["p95"], got["p99"]], want, rtol=0, atol=0)
    snap = h.snapshot()
    assert snap["count"] == len(xs)
    np.testing.assert_allclose(snap["mean"], xs.mean())
    np.testing.assert_allclose(snap["max"], xs.max())


def test_histogram_prometheus_buckets_cumulative(rng):
    h = LatencyHistogram(bounds_ms=[1.0, 10.0, 100.0])
    for v in (0.5, 5.0, 50.0, 500.0):
        h.record(v)
    rows = h.cumulative_buckets()
    assert rows == [(1.0, 1), (10.0, 2), (100.0, 3), (float("inf"), 4)]
    lines = h.prometheus_lines("lat_seconds")
    assert lines[0] == "# TYPE lat_seconds histogram"
    assert 'lat_seconds_bucket{le="+Inf"} 4' in lines
    assert any(line.startswith("lat_seconds_count") for line in lines)
    text = prometheus_text(counters={"reqs_total": 4},
                           histograms={"lat_seconds": h})
    assert "# TYPE lgbt_reqs_total counter" in text
    assert text.endswith("\n")


# -- live server: trace_id propagation, metrics, snapshots -------------------

@pytest.mark.serving
def test_trace_id_propagation_through_live_server(rng, tmp_path):
    """Acceptance: one trace_id links the request span, its micro-batch
    span and the batch's stage spans, in a trace that loads as Chrome
    trace-event JSON; shed responses echo the id."""
    bst = _train(rng)
    trace_path = tmp_path / "serve_trace.json"
    server = bst.serve(port=0, min_bucket=32, max_batch_rows=64,
                       trace=True, trace_out=str(trace_path))
    tid = new_trace_id()
    try:
        with ServingClient(server.host, server.port, timeout=60) as c:
            got = np.asarray(c.predict(rng.randn(5, 6), trace_id=tid))
            assert got.shape == (5,)
            # the response frame echoes the id (raw call to see the frame)
            resp = c._call({"op": "predict", "data": rng.randn(3, 6),
                            "raw_score": False, "trace_id": "echo-42"})
            assert resp["trace_id"] == "echo-42"
            # shed echo: saturate admission, next predict must shed WITH
            # the id attached to the typed exception
            while server.admission.try_acquire():
                pass
            with pytest.raises(ServerOverloaded) as ei:
                c.predict(rng.randn(2, 6), trace_id="shed-1")
            assert ei.value.trace_id == "shed-1"
    finally:
        server.stop()
    trace = json.loads(trace_path.read_text())
    linked = {"serve.request": 0, "serve.batch": 0,
              "serve_bin": 0, "serve_traverse": 0, "serve_queue": 0}
    for e in trace["traceEvents"]:
        if e.get("ph") != "B":
            continue
        t = e.get("args", {}).get("trace_id")
        if t == tid or (isinstance(t, list) and tid in t):
            if e["name"] in linked:
                linked[e["name"]] += 1
    assert all(v >= 1 for v in linked.values()), linked
    # stats carry the latency histogram section
    rep = server.report()
    assert validate_report(rep) == []
    assert rep["serving"]["latency_ms"]["count"] >= 2


@pytest.mark.serving
def test_metrics_op_prometheus_snapshot(rng):
    bst = _train(rng)
    server = bst.serve(port=0, min_bucket=32, max_batch_rows=64)
    try:
        with ServingClient(server.host, server.port, timeout=60) as c:
            c.predict(rng.randn(4, 6))
            text = c.metrics()
    finally:
        server.stop()
    assert "# TYPE lgbt_serving_requests_total counter" in text
    assert "lgbt_serving_requests_total 1" in text
    assert 'lgbt_serving_request_latency_seconds_bucket{le="+Inf"} 1' in text
    assert "lgbt_serving_batch_occupancy" in text
    # reliability counters ride along (process-wide table)
    assert "lgbt_serving_inflight" in text


@pytest.mark.serving
def test_stats_out_periodic_snapshots(rng, tmp_path):
    """--stats-out: periodic atomic schema-validated snapshots appear
    without any socket op, and a final one lands at stop."""
    bst = _train(rng)
    out = tmp_path / "stats.json"
    server = bst.serve(port=0, min_bucket=32, max_batch_rows=64,
                       stats_out=str(out), stats_interval_s=0.2)
    try:
        with ServingClient(server.host, server.port, timeout=60) as c:
            c.predict(rng.randn(3, 6))
        deadline = time.monotonic() + 30
        while not out.exists() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert out.exists(), "no snapshot within 30s at 0.2s interval"
        snap = json.loads(out.read_text())
        assert validate_report(snap) == []
    finally:
        server.stop()
    final = json.loads(out.read_text())
    assert validate_report(final) == []
    assert final["serving"]["requests"] >= 1


# -- tracing-off invariants --------------------------------------------------

@pytest.mark.serving
def test_tracing_adds_no_recompiles_to_warm_buckets(rng):
    """With buckets warm, enabling tracing must not grow the jit caches:
    spans are host-side only, so the compiled programs are untouched."""
    bst = _train(rng)
    server = bst.serve(port=0, min_bucket=32, max_batch_rows=64)
    try:
        with ServingClient(server.host, server.port, timeout=60) as c:
            c.predict(rng.randn(5, 6))            # steady-state, untraced
            before = server.registry.jit_entries()
            tracer = TraceRecorder(True)
            server.tracer = tracer
            server.stats.attach_tracer(tracer)
            for n in (3, 9, 17):
                c.predict(rng.randn(n, 6), trace_id=new_trace_id())
            after = server.registry.jit_entries()
    finally:
        server.stop()
    if before is not None:
        assert after == before, (before, after)
    assert len(tracer) > 0                         # spans did record


def test_training_trace_off_is_noop_and_model_identical(rng):
    """telemetry=False + no tracer: an attached-but-disabled recorder
    records nothing, and training with trace_out produces the exact same
    model text as without (tracing cannot perturb training)."""
    X = rng.randn(1500, 5)
    y = (X[:, 0] > 0).astype(float)
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "seed": 7, "min_data_in_leaf": 10}
    plain = lgb.train(dict(p), lgb.Dataset(X.copy(), label=y.copy()), 6)
    # a disabled-telemetry booster with a tracer attached records nothing
    bst2 = lgb.Booster(dict(p), lgb.Dataset(X.copy(), label=y.copy()))
    rec = TraceRecorder(True)
    bst2.gbdt.telemetry.tracer = rec
    for _ in range(3):
        bst2.update()
    bst2.gbdt._flush_pending()
    assert len(rec) == 0            # telemetry off → no phase spans at all
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        trace_path = os.path.join(td, "train_trace.json")
        traced = lgb.train(dict(p, trace_out=trace_path),
                           lgb.Dataset(X.copy(), label=y.copy()), 6)
        assert traced.model_to_string() == plain.model_to_string()
        trace = json.loads(open(trace_path).read())
    names = {e["name"] for e in trace["traceEvents"] if e.get("ph") == "B"}
    # training phase spans present (engine/gbdt phase timers as spans);
    # the tree phase name depends on the dispatch path taken
    assert "iteration" in names
    assert names & {"tree_train", "tree_dispatch", "gradients",
                    "pipeline_flush"}


# -- podtrace: per-rank export + cross-host merge ----------------------------

class _FakeNet:
    """Just enough DistributedNet surface for podtrace unit tests."""

    def __init__(self, rank, num_machines=2, clock_offset_s=0.0):
        self.rank = rank
        self.num_machines = num_machines
        self._off = clock_offset_s

    def allgather(self, payload):
        # rank 0's stamp on ITS clock: our clock minus the offset, posted
        # "now" (inside the caller's send/recv window, so midpoint error
        # is bounded by the call's rtt)
        return [("clk", 0, time.perf_counter() - self._off), payload]


def test_estimate_clock_offset_recovers_known_skew():
    from lightgbm_tpu.observability import podtrace
    off = podtrace.estimate_clock_offset(
        _FakeNet(rank=1, clock_offset_s=0.25), rounds=4)
    assert abs(off["offset_s"] - 0.25) < 0.01
    assert off["method"] == "kv-ping-midpoint"
    # rank 0 IS the reference clock, whatever its rounds measured
    off0 = podtrace.estimate_clock_offset(
        _FakeNet(rank=0, clock_offset_s=0.25), rounds=4)
    assert off0["offset_s"] == 0.0


def test_podtrace_merge_aligns_and_nests(tmp_path):
    from lightgbm_tpu.observability import podtrace

    clk = {"offset_s": 0.0, "rtt_s": 1e-4, "rounds": 8,
           "method": "kv-ping-midpoint"}
    r0 = TraceRecorder(True)
    with r0.span("iteration"):
        with r0.span("tree_dispatch"):
            pass
    time.sleep(0.02)
    r1 = TraceRecorder(True)      # later epoch, same host clock
    with r1.span("iteration"):
        pass
    base = str(tmp_path / "trace.json")
    p0 = podtrace.export_rank_trace(r0, base, net=_FakeNet(0),
                                    clock=dict(clk))
    p1 = podtrace.export_rank_trace(r1, base, net=_FakeNet(1),
                                    clock=dict(clk))
    assert p0.endswith(".rank0") and p1.endswith(".rank1")
    # single host: the path passes through unchanged
    assert podtrace.rank_trace_path(base, 0, 1) == base
    with open(p0) as fh:
        meta0 = json.load(fh)["otherData"]
    assert meta0["rank"] == 0 and meta0["process_count"] == 2
    assert "aligned_epoch_us" in meta0

    merged_path = str(tmp_path / "pod.json")
    merged = podtrace.merge_pod_trace([p0, p1], out=merged_path)
    with open(merged_path) as fh:            # valid Chrome trace JSON
        reloaded = json.load(fh)
    assert reloaded["otherData"]["pod_merge"] is True
    ev = merged["traceEvents"]
    assert {e["pid"] for e in ev} == {0, 1}  # pids rewritten to ranks
    pnames = {e["pid"]: e["args"]["name"] for e in ev
              if e.get("ph") == "M" and e["name"] == "process_name"}
    assert pnames[0].startswith("rank 0")
    assert pnames[1].startswith("rank 1")
    # same-host clocks (offset 0): rank 1's later-recorded span must land
    # LATER on the merged timeline than rank 0's earlier spans
    t0_end = max(e["ts"] for e in ev
                 if e["pid"] == 0 and e.get("ph") == "E")
    t1_beg = min(e["ts"] for e in ev
                 if e["pid"] == 1 and e.get("ph") == "B")
    assert t1_beg > t0_end
    # B/E well-nesting survives the merge on every (pid, tid) stream
    stacks = {}
    for e in ev:
        if e.get("ph") == "B":
            stacks.setdefault((e["pid"], e["tid"]), []).append(e["name"])
        elif e.get("ph") == "E":
            assert stacks[(e["pid"], e["tid"])].pop() == e["name"]
    assert not any(stacks.values())
    ts = [e["ts"] for e in ev if e.get("ph") in "BEi"]
    assert ts == sorted(ts)                  # merged timeline is monotone


def test_podtrace_offset_compensation(tmp_path):
    """A rank whose clock runs 0.5 s AHEAD exports aligned_epoch 0.5 s
    earlier; the merge therefore cancels the skew instead of showing the
    rank half a second late."""
    from lightgbm_tpu.observability import podtrace

    r0 = TraceRecorder(True)
    with r0.span("iteration"):
        pass
    r1 = TraceRecorder(True)
    with r1.span("iteration"):
        pass
    base = str(tmp_path / "t.json")
    clk0 = {"offset_s": 0.0, "rtt_s": 0.0, "rounds": 1, "method": "x"}
    p0 = podtrace.export_rank_trace(r0, base, net=_FakeNet(0), clock=clk0)
    skewed = {"offset_s": 0.5, "rtt_s": 0.0, "rounds": 1, "method": "x"}
    p1 = podtrace.export_rank_trace(r1, base, net=_FakeNet(1), clock=skewed)
    with open(p0) as fh:
        e0 = json.load(fh)["otherData"]["aligned_epoch_us"]
    with open(p1) as fh:
        e1 = json.load(fh)["otherData"]["aligned_epoch_us"]
    # r1 was created AFTER r0 on the same real clock, but claiming its
    # clock is 0.5 s ahead pulls its aligned epoch ~0.5 s BEFORE r0's
    assert e0 - e1 == pytest.approx(0.5e6, abs=0.1e6)
    merged = podtrace.merge_pod_trace([p0, p1])
    ranks = {m["rank"]: m for m in merged["otherData"]["ranks"]}
    assert ranks[1]["clock_offset_us"] == pytest.approx(0.5e6)


def test_podtrace_cli_merges(tmp_path, capsys):
    from lightgbm_tpu.observability import podtrace

    r = TraceRecorder(True)
    with r.span("iteration"):
        pass
    p0 = str(tmp_path / "a.json")
    r.save(p0)
    out = str(tmp_path / "merged.json")
    assert podtrace.main([out, p0, p0]) == 0
    assert "merged 2 rank trace(s)" in capsys.readouterr().out
    with open(out) as fh:
        merged = json.load(fh)
    # metadata-less inputs merge at offset 0 with list-index ranks
    assert {e["pid"] for e in merged["traceEvents"]} <= {0, 1}
    assert podtrace.main([out]) == 2         # usage error


# -- bench_serving.py --------------------------------------------------------

@pytest.mark.serving(timeout=300)
def test_bench_serving_smoke(tmp_path):
    """Tiny closed+open-loop run: exits 0, prints one JSON line, writes a
    BENCH_SERVING file that validates against the checked-in schema."""
    out = tmp_path / "BENCH_SERVING_smoke.json"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo_root, "bench_serving.py"),
         "--out", str(out), "--train-rows", "2000", "--trees", "5",
         "--requests", "24", "--clients", "2", "--qps", "30",
         "--open-seconds", "1", "--num-features", "6"],
        capture_output=True, text=True, env=env, timeout=280)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "closed_p99_ms" in line and "open_qps" in line
    report = json.loads(out.read_text())
    assert validate_report(report, BENCH_SERVING_SCHEMA) == []
    # schema v2: provenance pins the cost ledger the run was gated under
    assert report["schema_version"] == 2
    sha = report["provenance"]["cost_ledger_sha256"]
    assert isinstance(sha, str) and len(sha) == 64
    assert report["closed_loop"]["ok"] > 0
    assert report["open_loop"]["requests"] >= 30 * 1
    assert report["server"]["batches"] > 0
