"""Vectorized NDCG/MAP metrics vs direct per-query reference loops, plus an
MSLR-scale timing bound (VERDICT r2 weak #6)."""

import time

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import Metadata
from lightgbm_tpu.metrics import MapMetric, NDCGMetric
from lightgbm_tpu.rank_objective import default_label_gain


def _make_rank_data(rng, nq, qmin=2, qmax=40):
    sizes = rng.randint(qmin, qmax, size=nq)
    n = int(sizes.sum())
    md = Metadata(n)
    md.set_label(rng.randint(0, 5, size=n).astype(np.float64))
    md.set_group(sizes)
    return md, n, sizes


def _ndcg_loop(md, score, ks):
    """Per-query loop (the round-2 implementation)."""
    gain = default_label_gain()
    qb = md.query_boundaries
    out = {}
    for k in ks:
        total = 0.0
        for qi in range(len(qb) - 1):
            lab = md.label[qb[qi]:qb[qi + 1]].astype(np.int64)
            sc = score[qb[qi]:qb[qi + 1]]
            ideal = np.sort(lab)[::-1][:k]
            disc = 1.0 / np.log2(np.arange(len(ideal)) + 2.0)
            maxdcg = (gain[ideal] * disc).sum()
            if maxdcg <= 0:
                total += 1.0
            else:
                order = np.argsort(-sc, kind="mergesort")
                top = lab[order][:k]
                disc = 1.0 / np.log2(np.arange(len(top)) + 2.0)
                total += (gain[top] * disc).sum() / maxdcg
        out[k] = total / (len(qb) - 1)
    return out


def _map_loop(md, score, ks):
    qb = md.query_boundaries
    out = {}
    for k in ks:
        total = 0.0
        for qi in range(len(qb) - 1):
            lab = (md.label[qb[qi]:qb[qi + 1]] > 0).astype(np.float64)
            order = np.argsort(-score[qb[qi]:qb[qi + 1]], kind="mergesort")
            rel = lab[order][:k]
            hits = np.cumsum(rel)
            denom = np.arange(1, len(rel) + 1)
            npos = rel.sum()
            total += (rel * hits / denom).sum() / npos if npos > 0 else 0.0
        out[k] = total / (len(qb) - 1)
    return out


def test_ndcg_matches_per_query_loop(rng):
    md, n, _ = _make_rank_data(rng, 150)
    score = rng.randn(n)
    m = NDCGMetric(Config.from_params({"eval_at": "1,3,5,10"}))
    m.init(md, n)
    got = dict((int(name.split("@")[1]), val) for name, val in m.eval(score))
    want = _ndcg_loop(md, score, [1, 3, 5, 10])
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-12, err_msg=str(k))


def test_map_matches_per_query_loop(rng):
    md, n, _ = _make_rank_data(rng, 150)
    score = rng.randn(n)
    m = MapMetric(Config.from_params({"eval_at": "1,3,5,10"}))
    m.init(md, n)
    got = dict((int(name.split("@")[1]), val) for name, val in m.eval(score))
    want = _map_loop(md, score, [1, 3, 5, 10])
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-12, err_msg=str(k))


def test_ndcg_score_ties_keep_doc_order(rng):
    md, n, _ = _make_rank_data(rng, 40)
    score = np.repeat(rng.randn(5), (n + 4) // 5)[:n]  # heavy ties
    m = NDCGMetric(Config.from_params({"eval_at": "5"}))
    m.init(md, n)
    got = m.eval(score)[0][1]
    want = _ndcg_loop(md, score, [5])[5]
    np.testing.assert_allclose(got, want, rtol=1e-12)


def test_mslr_scale_eval_under_one_second(rng):
    # MSLR-WEB30K shape: ~31k queries, ~120 docs each
    md, n, _ = _make_rank_data(rng, 31000, 60, 180)
    score = rng.randn(n)
    m = NDCGMetric(Config.from_params({"eval_at": "1,3,5"}))
    m.init(md, n)
    m.eval(score)  # warm caches
    t0 = time.perf_counter()
    m.eval(score)
    dt = time.perf_counter() - t0
    assert dt < 1.0, f"NDCG eval took {dt:.2f}s at MSLR scale"
